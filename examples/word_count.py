"""WordCount: the canonical FlatMap + ReduceByKey pipeline.

Reference: /root/reference/examples/word_count/word_count.hpp:35-57
(FlatMap split + ReduceByKey sum). Two variants:

* ``word_count``     — faithful text pipeline (host storage for strings)
* ``word_count_fixed`` — TPU-native: words packed into fixed-width byte
  vectors on device, the whole aggregation running as jitted programs.
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo root on sys.path for CLI runs)


import numpy as np

from thrill_tpu.api import Context, FieldReduce


def word_count(ctx: Context, path_or_lines):
    """Returns a DIA of (word, count) pairs from text."""
    if isinstance(path_or_lines, str):
        lines = ctx.ReadLines(path_or_lines)
    else:
        lines = ctx.Distribute(list(path_or_lines), storage="host")
    return (lines
            .FlatMap(lambda line: line.split())
            .Map(lambda w: (w, 1))
            .ReduceByKey(lambda kv: kv[0],
                         lambda a, b: (a[0], a[1] + b[1])))


MAX_WORD = 16   # device variant: words truncated/padded to 16 bytes


def pack_words(words) -> np.ndarray:
    """Pack a list of strings into [n, MAX_WORD] uint8 (zero padded).

    Row i always corresponds to words[i] — empty strings keep their
    (all-zero) row; the byte packing itself is one vectorized gather."""
    enc = [w.encode("utf-8")[:MAX_WORD] for w in words]
    lens = np.fromiter((len(b) for b in enc), np.int64, count=len(enc))
    buf = np.frombuffer(b"".join(enc), dtype=np.uint8)
    if buf.size == 0:
        return np.zeros((len(words), MAX_WORD), dtype=np.uint8)
    offs = np.concatenate(([0], np.cumsum(lens)))[:-1]
    idx = offs[:, None] + np.arange(MAX_WORD)[None, :]
    valid = np.arange(MAX_WORD)[None, :] < lens[:, None]
    return np.where(valid, buf[np.where(valid, idx, 0)],
                    0).astype(np.uint8)


def word_count_text_device(ctx: Context, path: str,
                           max_word: int = MAX_WORD):
    """Device WordCount straight from a text file: vectorized
    tokenization into packed byte rows (ctx.ReadWordsPacked), then the
    whole aggregation as jitted device programs. Returns a DIA of
    {"w": [max_word] u8, "c": count} rows (use
    thrill_tpu.core.text.unpack_words to recover strings)."""
    import jax.numpy as jnp

    words = ctx.ReadWordsPacked(path, max_word=max_word)
    # ones_like(..[..., 0]) yields [n] on the batched device tree and a
    # scalar on a single host item — valid under both Map contracts
    pairs = words.Map(lambda t: {
        "w": t["w"],
        "c": jnp.ones_like(t["w"][..., 0], dtype=jnp.int64)})
    # declarative functor: the host local phase fuses the whole
    # aggregation into one native hash-probe pass (the analog of the
    # reference's std::plus being template-inlined into its table)
    return pairs.ReduceByKey(lambda t: t["w"],
                             FieldReduce({"w": "first", "c": "sum"}))


def word_count_fixed(ctx: Context, packed: np.ndarray):
    """Device WordCount over pre-packed fixed-width words.

    The reduce runs fully on device: key = the byte vector itself
    (encoded to uint64 words), value = count.
    """
    d = ctx.Distribute({"w": packed,
                        "c": np.ones(len(packed), dtype=np.int64)})
    return d.ReduceByKey(lambda t: t["w"],
                         FieldReduce({"w": "first", "c": "sum"}))


def main():
    import argparse
    parser = argparse.ArgumentParser(description="thrill_tpu WordCount")
    parser.add_argument("input", help="text file/glob")
    parser.add_argument("--top", type=int, default=10)
    args = parser.parse_args()

    from thrill_tpu.api import Run

    def job(ctx):
        counts = word_count(ctx, args.input).AllGather()
        counts.sort(key=lambda kv: -kv[1])
        for w, c in counts[:args.top]:
            print(f"{c:8d}  {w}")

    Run(job)


if __name__ == "__main__":
    main()
