"""Distributed logistic regression by batch gradient descent.

Reference: /root/reference/examples/logistic_regression/ — per-worker
gradient partial sums AllReduce'd each round. TPU-native: the gradient
is a batched matmul on device columns (MXU), summed via the Sum action
(psum over the mesh).
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo root on sys.path for CLI runs)


import numpy as np

from thrill_tpu.api import Context


def logistic_regression(ctx: Context, X: np.ndarray, y: np.ndarray,
                        iterations: int = 50, lr: float = 0.5):
    import jax.numpy as jnp

    n, dim = X.shape
    data = ctx.Distribute({"x": X.astype(np.float64),
                           "y": y.astype(np.float64)}).Cache() \
        .Keep(iterations + 1)
    w = np.zeros(dim)
    for _ in range(iterations):
        wj = jnp.asarray(w)

        def grad(t):
            z = t["x"] @ wj
            p = 1.0 / (1.0 + jnp.exp(-z))
            g = (p - t["y"])[:, None] * t["x"]
            return g

        gsum = data.Map(grad).Sum()
        w = w - lr * np.asarray(gsum) / n
    return w


def main():
    from thrill_tpu.api import Run

    def job(ctx):
        rng = np.random.default_rng(0)
        n, dim = 5000, 5
        true_w = rng.normal(size=dim)
        X = rng.normal(size=(n, dim))
        y = (X @ true_w + 0.1 * rng.normal(size=n) > 0).astype(np.float64)
        w = logistic_regression(ctx, X, y)
        acc = np.mean((X @ w > 0) == (y > 0.5))
        print(f"train acc {acc:.3f}, w = {np.round(w, 3)}")

    Run(job)


if __name__ == "__main__":
    main()
