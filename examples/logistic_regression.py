"""Distributed logistic regression by batch gradient descent.

Reference: /root/reference/examples/logistic_regression/ — per-worker
gradient partial sums AllReduce'd each round. TPU-native: the gradient
is a batched matmul on device columns (MXU), summed via the Sum action
(psum over the mesh).
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo root on sys.path for CLI runs)


import numpy as np

from thrill_tpu.api import Context


def _lr_grad(t, w):
    # module-level + Bind: the model vector is a runtime-bound operand,
    # so every gradient round reuses ONE compiled program (an in-loop
    # closure over w would recompile per iteration, 20-40 s on TPU)
    import jax.numpy as jnp
    z = t["x"] @ w
    p = 1.0 / (1.0 + jnp.exp(-z))
    return (p - t["y"])[:, None] * t["x"]


def logistic_regression(ctx: Context, X: np.ndarray, y: np.ndarray,
                        iterations: int = 50, lr: float = 0.5):
    import jax.numpy as jnp

    from thrill_tpu.api import Bind

    n, dim = X.shape
    data = ctx.Distribute({"x": X.astype(np.float64),
                           "y": y.astype(np.float64)}).Cache() \
        .Keep(iterations + 1)
    # the whole descent stays in jax's async dispatch stream: Sum
    # returns a device vector, the update is eager device math, and w
    # re-enters through Bind — zero blocking syncs per iteration
    w = jnp.zeros(dim)
    for _ in range(iterations):
        gsum = data.Map(Bind(_lr_grad, w)).Sum(device=True)
        w = w - lr * gsum / n
    return np.asarray(w)


def main():
    from thrill_tpu.api import Run

    def job(ctx):
        rng = np.random.default_rng(0)
        n, dim = 5000, 5
        true_w = rng.normal(size=dim)
        X = rng.normal(size=(n, dim))
        y = (X @ true_w + 0.1 * rng.normal(size=n) > 0).astype(np.float64)
        w = logistic_regression(ctx, X, y)
        acc = np.mean((X @ w > 0) == (y > 0.5))
        print(f"train acc {acc:.3f}, w = {np.round(w, 3)}")

    Run(job)


if __name__ == "__main__":
    main()
