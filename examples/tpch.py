"""TPC-H-style join query over generated order/lineitem tables.

Reference: /root/reference/examples/tpch/ — a join of lineitems against
orders with a filter + aggregation (the reference runs its InnerJoin on
parsed TPC-H tables; here tables are generated columnar data).

Query (Q3-lite): revenue per order priority for orders in a date range:
  SELECT o.priority, SUM(l.extendedprice * (1 - l.discount))
  FROM orders o JOIN lineitem l ON o.key = l.orderkey
  WHERE o.date < CUTOFF GROUP BY o.priority
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo root on sys.path for CLI runs)

import numpy as np

from thrill_tpu.api import Context, InnerJoin

NUM_PRIORITIES = 5


def generate_tables(num_orders: int, lines_per_order: int = 4,
                    seed: int = 0):
    rng = np.random.default_rng(seed)
    orders = {
        "key": np.arange(num_orders, dtype=np.int64),
        "date": rng.integers(0, 2500, num_orders).astype(np.int64),
        "prio": rng.integers(0, NUM_PRIORITIES, num_orders).astype(np.int64),
    }
    m = num_orders * lines_per_order
    lineitem = {
        "orderkey": rng.integers(0, num_orders, m).astype(np.int64),
        "price": rng.integers(1, 1000, m).astype(np.int64),
        "discount_pct": rng.integers(0, 10, m).astype(np.int64),
    }
    return orders, lineitem


def q3_lite(ctx: Context, orders, lineitem, cutoff: int = 1250):
    o = ctx.Distribute(orders).Filter(lambda t: t["date"] < cutoff)
    l = ctx.Distribute(lineitem)
    joined = InnerJoin(
        o, l, lambda t: t["key"], lambda t: t["orderkey"],
        lambda ot, lt: {"prio": ot["prio"],
                        "rev": lt["price"] * (100 - lt["discount_pct"])})
    per_prio = joined.ReduceToIndex(
        lambda t: t["prio"], lambda a, b: {"prio": a["prio"],
                                           "rev": a["rev"] + b["rev"]},
        NUM_PRIORITIES, neutral={"prio": 0, "rev": 0})
    return np.array([int(t["rev"]) for t in per_prio.AllGather()])


def q3_dense(orders, lineitem, cutoff: int = 1250):
    sel = orders["date"] < cutoff
    okey = set(orders["key"][sel].tolist())
    prio = {int(k): int(p) for k, p in zip(orders["key"], orders["prio"])}
    out = np.zeros(NUM_PRIORITIES, dtype=np.int64)
    for k, pr, dc in zip(lineitem["orderkey"], lineitem["price"],
                         lineitem["discount_pct"]):
        if int(k) in okey:
            out[prio[int(k)]] += int(pr) * (100 - int(dc))
    return out


def main():
    from thrill_tpu.api import Run

    def job(ctx):
        orders, lineitem = generate_tables(10000)
        rev = q3_lite(ctx, orders, lineitem)
        for p, r in enumerate(rev):
            print(f"priority {p}: revenue {r}")

    Run(job)


if __name__ == "__main__":
    main()
