"""Tutorial: a guided tour of thrill_tpu's DIA pipelines.

Reference analog: /root/reference/examples/tutorial (the commented
first-steps program). Run it:   python examples/tutorial.py
"""

from __future__ import annotations

import _bootstrap  # noqa: F401

import numpy as np

from thrill_tpu.api import Run, Zip


def job(ctx):
    # 1. Sources: Generate produces 0..n-1; Distribute ships your data.
    nums = ctx.Generate(1000)

    # 2. Local ops chain lazily and fuse into one device program.
    evens = nums.Map(lambda x: x * 3).Filter(lambda x: x % 2 == 0)

    # 3. Actions trigger execution. Keep() lets a DIA be reused.
    evens.Keep()
    print("count:", evens.Keep().Size())
    print("sum:  ", int(evens.Sum()))

    # 4. Distributed ops: ReducePair aggregates (key, value) pairs
    #    through a hash exchange over the device mesh.
    hist = (ctx.Generate(10_000)
               .Map(lambda x: (x % 7, 1))
               .ReducePair(lambda a, b: a + b))
    print("histogram:", sorted((int(k), int(v))
                               for k, v in hist.AllGather()))

    # 5. Sort is a distributed sample sort; equal keys keep their
    #    original order (always stable).
    rng = np.random.default_rng(0)
    vals = rng.integers(0, 100, 5000).astype(np.int64)
    srt = ctx.Distribute(vals).Sort()
    head = [int(x) for x in srt.AllGather()][:5]
    print("sorted head:", head)

    # 6. Zip aligns two DIAs element-wise (with realignment exchange).
    a = ctx.Generate(100)
    b = ctx.Generate(100, fn=lambda i: i * i)
    z = Zip(a, b, zip_fn=lambda x, y: y - x)
    print("zip tail:", [int(v) for v in z.AllGather()][-3:])

    # 7. overall_stats summarizes traffic + memory at close.
    print("stats:", ctx.overall_stats())


if __name__ == "__main__":
    Run(job)
