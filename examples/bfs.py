"""Breadth-first search over an edge-list graph.

Reference: /root/reference/examples/bfs/ — level-synchronous BFS:
the frontier joins the edge list to produce next-level candidates,
ReduceByKey picks the minimum discovered level per node, iterate.
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo root on sys.path for CLI runs)

import numpy as np

from thrill_tpu.api import Context, InnerJoin


def bfs_levels(ctx: Context, edges: np.ndarray, num_nodes: int,
               source: int = 0, max_iters: int = 0) -> np.ndarray:
    """edges: [m, 2] directed int64. Returns level per node (-1 =
    unreachable)."""
    levels = np.full(num_nodes, -1, dtype=np.int64)
    levels[source] = 0
    frontier = np.array([source], dtype=np.int64)
    max_iters = max_iters or num_nodes

    edges_dia = ctx.Distribute({"s": edges[:, 0].astype(np.int64),
                                "d": edges[:, 1].astype(np.int64)}) \
        .Cache().Keep(max_iters + 1)

    level = 0
    while len(frontier) and level < max_iters:
        f = ctx.Distribute({"n": frontier})
        nxt = InnerJoin(edges_dia, f,
                        lambda e: e["s"], lambda t: t["n"],
                        lambda e, t: {"d": e["d"]})
        cand = np.unique(np.asarray(
            [int(t["d"]) for t in nxt.AllGather()], dtype=np.int64))
        new = cand[levels[cand] < 0] if len(cand) else cand
        level += 1
        levels[new] = level
        frontier = new
    return levels


def bfs_dense(edges: np.ndarray, num_nodes: int, source: int = 0):
    from collections import deque
    adj = [[] for _ in range(num_nodes)]
    for s, d in edges:
        adj[s].append(d)
    lv = np.full(num_nodes, -1, dtype=np.int64)
    lv[source] = 0
    q = deque([source])
    while q:
        u = q.popleft()
        for v in adj[u]:
            if lv[v] < 0:
                lv[v] = lv[u] + 1
                q.append(v)
    return lv


def main():
    import argparse
    parser = argparse.ArgumentParser()
    parser.add_argument("--nodes", type=int, default=1000)
    parser.add_argument("--edges", type=int, default=5000)
    args = parser.parse_args()

    from thrill_tpu.api import Run

    def job(ctx):
        rng = np.random.default_rng(0)
        edges = rng.integers(0, args.nodes, (args.edges, 2)).astype(np.int64)
        lv = bfs_levels(ctx, edges, args.nodes)
        reach = int((lv >= 0).sum())
        print(f"reachable {reach}/{args.nodes}, max level {lv.max()}")

    Run(job)


if __name__ == "__main__":
    main()
