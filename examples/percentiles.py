"""Percentiles of a distributed dataset via Sort + ZipWithIndex.

Reference: /root/reference/examples/percentiles/ — sort the values and
probe rank positions.
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo root on sys.path for CLI runs)

import numpy as np

from thrill_tpu.api import Context


def percentiles(ctx: Context, values: np.ndarray, qs=(50, 90, 95, 99)):
    n = len(values)
    wanted = {int(np.clip(int(q / 100.0 * n), 0, n - 1)): q for q in qs}
    idx_dev = np.array(sorted(wanted), dtype=np.int64)

    import jax.numpy as jnp
    tgt = jnp.asarray(idx_dev)

    s = ctx.Distribute(np.asarray(values, dtype=np.int64)).Sort()
    ranked = s.ZipWithIndex(lambda v, i: (i, v))
    picked = ranked.Filter(lambda t: jnp.isin(t[0], tgt))
    out = {}
    for i, v in picked.AllGather():
        out[wanted[int(i)]] = int(v)
    return out


def main():
    from thrill_tpu.api import Run

    def job(ctx):
        rng = np.random.default_rng(0)
        vals = rng.integers(0, 10 ** 9, 100000)
        print(percentiles(ctx, vals))

    Run(job)


if __name__ == "__main__":
    main()
