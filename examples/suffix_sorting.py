"""Suffix array construction by prefix doubling — the Sort-heaviest user.

Reference: /root/reference/examples/suffix_sorting/prefix_doubling.cpp
(also DC3/DC7 in dc3.cpp/dc7.cpp): iterative rank refinement where each
round sorts (rank[i], rank[i+2^k], i) triples — log n distributed sorts.

TPU-native: ranks live as device columns; each doubling round is one
device Sort + neighbor-compare rank assignment (PrefixSum of boundary
flags), the exact structure the reference runs over its sample sort.
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo root on sys.path for CLI runs)


import numpy as np

from thrill_tpu.api import Context


def _sa_rank_key(t):
    # module-level (identity-stable): each doubling round reuses the
    # same compiled sort executable — a fresh lambda per round would
    # recompile every round (20-40 s each on TPU)
    return (t["r1"], t["r2"])


def suffix_array(ctx: Context, text: np.ndarray) -> np.ndarray:
    """text: [n] uint8. Returns the suffix array [n] int64.

    The doubling loop is device-resident: sorted columns come back as
    device arrays (AllGatherArrays), the rank recomputation is eager
    jnp math, and Distribute re-splits device arrays without a host
    round trip — the only per-round sync is the scalar
    distinct-rank count that decides loop termination."""
    import jax.numpy as jnp

    n = len(text)
    if n == 0:
        return np.array([], dtype=np.int64)

    # initial ranks = byte values; sentinel handling via +1
    rank = jnp.asarray(text.astype(np.int64) + 1)
    idx = jnp.arange(n, dtype=jnp.int64)
    h = 1
    while True:
        rank2 = jnp.zeros(n, dtype=jnp.int64)
        if h < n:
            rank2 = rank2.at[:n - h].set(rank[h:])

        d = ctx.Distribute({"i": idx, "r1": rank, "r2": rank2})
        s = d.Sort(key_fn=_sa_rank_key)
        # columnar egress in ranked worker order = global sort order
        cols = s.AllGatherArrays()
        si, r1, r2 = cols["i"], cols["r1"], cols["r2"]

        # new ranks: 1 + prefix count of strict (r1, r2) boundaries
        boundary = jnp.concatenate([
            jnp.ones(1, jnp.int64),
            ((r1[1:] != r1[:-1]) | (r2[1:] != r2[:-1])).astype(jnp.int64)])
        new_rank_sorted = jnp.cumsum(boundary)
        rank = jnp.zeros(n, dtype=jnp.int64).at[si].set(new_rank_sorted)
        if int(new_rank_sorted[-1]) == n:       # termination sync
            return np.asarray(si, dtype=np.int64)
        h *= 2
        if h >= 2 * n:
            return np.asarray(si, dtype=np.int64)


def suffix_array_quadrupling(ctx: Context, text: np.ndarray) -> np.ndarray:
    """Prefix quadrupling: rank refinement advancing h by 4x per round
    with (rank[i], rank[i+h], rank[i+2h], rank[i+3h]) quadruple keys —
    half the distributed sorts of doubling at wider keys (reference:
    examples/suffix_sorting/prefix_quadrupling.cpp)."""
    n = len(text)
    if n == 0:
        return np.array([], dtype=np.int64)

    rank = text.astype(np.int64) + 1
    idx = np.arange(n, dtype=np.int64)
    h = 1
    while True:
        def shifted(k):
            out = np.zeros(n, dtype=np.int64)
            if k < n:
                out[:n - k] = rank[k:]
            return out

        r2, r3, r4 = shifted(h), shifted(2 * h), shifted(3 * h)
        d = ctx.Distribute({"i": idx, "a": rank, "b": r2, "c": r3,
                            "d": r4})
        got = d.Sort(
            key_fn=lambda t: (t["a"], t["b"], t["c"], t["d"])).AllGather()
        si = np.array([int(t["i"]) for t in got])
        cols = [np.array([int(t[k]) for t in got])
                for k in ("a", "b", "c", "d")]
        boundary = np.ones(n, dtype=np.int64)
        neq = np.zeros(n - 1, dtype=bool)
        for c in cols:
            neq |= c[1:] != c[:-1]
        boundary[1:] = neq.astype(np.int64)
        new_rank_sorted = np.cumsum(boundary)
        rank = np.zeros(n, dtype=np.int64)
        rank[si] = new_rank_sorted
        if new_rank_sorted[-1] == n:
            return si
        h *= 4
        if h >= 4 * n:
            return si


def dc3_suffix_array(ctx: Context, text: np.ndarray) -> np.ndarray:
    """DC3 (difference cover mod 3, a.k.a. skew) suffix array.

    Reference: /root/reference/examples/suffix_sorting/dc3.cpp — the
    heaviest recursive Sort stress test of the reference suite. The
    heavy phases ride the device: the (t_i, t_{i+1}, t_{i+2}) triple
    sort of the mod-1/mod-2 sample and the (t_i, rank_{i+1}) sort of
    the mod-0 class are DIA Sorts at every recursion level; lexicographic
    naming and the class-aware 3-way merge are linear host passes.
    """
    T = np.asarray(text, dtype=np.int64) + 1     # 0 reserved as sentinel
    return _dc3(ctx, T)


def _dc3(ctx: Context, T: np.ndarray) -> np.ndarray:
    n = len(T)
    if n <= 3:
        return np.array(sorted(range(n),
                               key=lambda i: tuple(T[i:]) + (0,)),
                        dtype=np.int64)

    # canonical Kärkkäinen–Sanders counts: when n % 3 == 1 the sample
    # gains the dummy position n (triple (0,0,0)), so the mod-1 section
    # of the recursion string ends with a unique smallest terminator
    n0 = (n + 2) // 3
    n1 = (n + 1) // 3
    ext = n0 - n1                    # 1 iff n % 3 == 1
    m = n + ext
    Tp = np.concatenate([T, np.zeros(3 + ext, dtype=np.int64)])
    s12 = np.array([i for i in range(m) if i % 3 != 0], dtype=np.int64)

    # device sort of the sample triples (the hot phase)
    d = ctx.Distribute({"i": s12, "a": Tp[s12], "b": Tp[s12 + 1],
                        "c": Tp[s12 + 2]})
    got = d.Sort(key_fn=lambda t: (t["a"], t["b"], t["c"])).AllGather()
    order = np.array([int(t["i"]) for t in got], dtype=np.int64)
    trip = np.array([[int(t["a"]), int(t["b"]), int(t["c"])]
                     for t in got], dtype=np.int64)

    # lexicographic names: 1 + count of strict triple boundaries
    boundary = np.ones(len(order), dtype=np.int64)
    if len(order) > 1:
        boundary[1:] = np.any(trip[1:] != trip[:-1], axis=1)
    names_sorted = np.cumsum(boundary)
    num_names = int(names_sorted[-1])
    name_of = np.zeros(m + 3, dtype=np.int64)
    name_of[order] = names_sorted

    if num_names < len(s12):
        # names collide: recurse on the sample string (mod-1 positions
        # then mod-2 positions, the canonical DC3 arrangement)
        ones = np.array([i for i in range(m) if i % 3 == 1])
        twos = np.array([i for i in range(m) if i % 3 == 2])
        R = np.concatenate([name_of[ones], name_of[twos]])
        SA_R = _dc3(ctx, R)
        k1 = len(ones)
        SA12 = np.where(SA_R < k1, 1 + 3 * SA_R, 2 + 3 * (SA_R - k1))
    else:
        SA12 = order

    # rank of each sample suffix in SA12 (1-based; 0 = beyond end)
    rank12 = np.zeros(m + 3, dtype=np.int64)
    rank12[SA12] = np.arange(1, len(SA12) + 1)
    # the dummy (position n, empty suffix) leaves the output
    SA12 = SA12[SA12 < n]

    # device sort of the mod-0 class by (t_i, rank_{i+1})
    s0 = np.array([i for i in range(n) if i % 3 == 0], dtype=np.int64)
    d0 = ctx.Distribute({"i": s0, "a": Tp[s0], "r": rank12[s0 + 1]})
    got0 = d0.Sort(key_fn=lambda t: (t["a"], t["r"])).AllGather()
    SA0 = np.array([int(t["i"]) for t in got0], dtype=np.int64)

    # class-aware linear merge (reference: dc3.cpp merge comparators)
    def leq12(i, j):
        """suffix i (mod 1 or 2) <= suffix j (mod 0)?"""
        if i % 3 == 1:
            return (Tp[i], rank12[i + 1]) <= (Tp[j], rank12[j + 1])
        return (Tp[i], Tp[i + 1], rank12[i + 2]) <= \
            (Tp[j], Tp[j + 1], rank12[j + 2])

    out = np.empty(n, dtype=np.int64)
    a = b = k = 0
    while a < len(SA12) and b < len(SA0):
        if leq12(int(SA12[a]), int(SA0[b])):
            out[k] = SA12[a]
            a += 1
        else:
            out[k] = SA0[b]
            b += 1
        k += 1
    while a < len(SA12):
        out[k] = SA12[a]
        a += 1
        k += 1
    while b < len(SA0):
        out[k] = SA0[b]
        b += 1
        k += 1
    return out


def suffix_array_dense(text: np.ndarray) -> np.ndarray:
    s = bytes(text)
    return np.array(sorted(range(len(s)), key=lambda i: s[i:]),
                    dtype=np.int64)


# DC7 difference cover: {0, 1, 3} mod 7 (differences cover Z_7), so 3/7
# of positions are sampled and any two residues share an aligning shift
DC7_D = (0, 1, 3)
# SHIFT[a][b] = min t >= 0 with (a+t) % 7 in D and (b+t) % 7 in D
DC7_SHIFT = [[min(t for t in range(7)
                  if (a + t) % 7 in DC7_D and (b + t) % 7 in DC7_D)
              for b in range(7)] for a in range(7)]


def dc7_suffix_array(ctx: Context, text: np.ndarray) -> np.ndarray:
    """DC7 (difference cover mod 7) suffix array.

    Reference: /root/reference/examples/suffix_sorting/dc7.cpp — like
    DC3 but samples 3/7 of positions with the perfect difference cover
    {0,1,3} mod 7, so each recursion level shrinks by 3/7 instead of
    2/3 and sorts wider (7-char) tuples: fewer, fatter device Sorts,
    the shape the MXU-era sort engine prefers. The sample 7-tuple sort
    and the batched non-sample class sort ride the device DIA Sort;
    naming and the comparator merge are linear host passes.
    """
    return _dc7(ctx, np.asarray(text, dtype=np.int64))


def _dc7(ctx: Context, S: np.ndarray) -> np.ndarray:
    """Suffix array of an arbitrary non-negative int string S."""
    n = len(S)
    if n <= 16:
        return np.array(sorted(range(n),
                               key=lambda i: tuple(S[i:]) + (-1,)),
                        dtype=np.int64)

    # internal shift so 0 is reserved for padding/terminators: zeros
    # then appear only in the tail, making every zero-containing
    # 7-tuple position-unique (shorter-suffix-sorts-first semantics)
    T = S + 1
    Tp = np.concatenate([T, np.zeros(14, dtype=np.int64)])

    res = np.arange(n) % 7
    s_cls = [np.flatnonzero(res == c).astype(np.int64) for c in range(7)]
    s_all = np.concatenate([s_cls[c] for c in DC7_D])

    # ---- device sort of the sample 7-tuples (naming phase) ----------
    cols = {f"c{k}": Tp[s_all + k] for k in range(7)}
    d = ctx.Distribute({"i": s_all, **cols})
    got = d.Sort(key_fn=lambda t: tuple(t[f"c{k}"] for k in range(7))) \
        .AllGather()
    order = np.array([int(t["i"]) for t in got], dtype=np.int64)
    tup = np.array([[int(t[f"c{k}"]) for k in range(7)] for t in got],
                   dtype=np.int64)

    boundary = np.ones(len(order), dtype=np.int64)
    if len(order) > 1:
        boundary[1:] = np.any(tup[1:] != tup[:-1], axis=1)
    names_sorted = np.cumsum(boundary)
    num_names = int(names_sorted[-1])
    name_of = np.zeros(n + 14, dtype=np.int64)
    name_of[order] = names_sorted

    if num_names < len(s_all):
        # recursion string: class sections joined by 0 terminators (a
        # unique-smallest section end keeps cross-section comparisons
        # from ever being decided by wrapped-around names; the
        # recursion re-shifts internally, so 0 stays reserved)
        sections = [name_of[s_cls[c]] for c in DC7_D]
        R = np.concatenate([sections[0], [0], sections[1], [0],
                            sections[2]])
        pos_map = np.concatenate([s_cls[DC7_D[0]], [-1],
                                  s_cls[DC7_D[1]], [-1],
                                  s_cls[DC7_D[2]]])
        SA_R = _dc7(ctx, R)
        SA12 = pos_map[SA_R]
        SA12 = SA12[SA12 >= 0]
    else:
        SA12 = order

    rank7 = np.zeros(n + 14, dtype=np.int64)
    rank7[SA12] = np.arange(1, len(SA12) + 1)

    # ---- one batched device sort of the non-sample classes ----------
    # class c orders by (T[i..i+tc-1], rank7[i+tc]); keys are laid out
    # (class, ch0.., rank, 0-pad) so one Sort covers all four classes
    ns_cls = [c for c in range(7) if c not in DC7_D]
    ns_pos = np.concatenate([s_cls[c] for c in ns_cls])
    if len(ns_pos):
        tcs = np.array([DC7_SHIFT[c][c] for c in range(7)], dtype=np.int64)
        tmax = int(tcs[ns_cls].max())              # = 3 for {0,1,3}
        keys = np.zeros((len(ns_pos), tmax + 2), dtype=np.int64)
        keys[:, 0] = ns_pos % 7
        for c in ns_cls:                           # 4 vectorized fills
            mask = ns_pos % 7 == c
            pos = ns_pos[mask]
            tc = int(tcs[c])
            keys[np.flatnonzero(mask)[:, None], 1 + np.arange(tc)] = \
                Tp[pos[:, None] + np.arange(tc)]
            keys[mask, 1 + tc] = rank7[pos + tc]
        dn = ctx.Distribute({"i": ns_pos,
                             **{f"k{j}": keys[:, j]
                                for j in range(tmax + 2)}})
        gotn = dn.Sort(key_fn=lambda t: tuple(t[f"k{j}"]
                                              for j in range(tmax + 2))) \
            .AllGather()
        by_cls = {c: [] for c in ns_cls}
        for t in gotn:
            by_cls[int(t["k0"])].append(int(t["i"]))
        seqs = [SA12.tolist()] + [by_cls[c] for c in ns_cls]
    else:
        seqs = [SA12.tolist()]

    # ---- comparator merge of the 5 sorted sequences -----------------
    import heapq
    from functools import cmp_to_key

    def cmp(i: int, j: int) -> int:
        t = DC7_SHIFT[i % 7][j % 7]
        for k in range(t):
            if Tp[i + k] != Tp[j + k]:
                return -1 if Tp[i + k] < Tp[j + k] else 1
        ri, rj = rank7[i + t], rank7[j + t]
        return -1 if ri < rj else (1 if ri > rj else 0)

    out = np.fromiter(
        heapq.merge(*seqs, key=cmp_to_key(cmp)), dtype=np.int64, count=n)
    return out


def wavelet_tree(ctx: Context, text: np.ndarray, bits: int = 8):
    """Wavelet matrix (level-ordered wavelet tree) of a byte sequence.

    Reference: /root/reference/examples/suffix_sorting wavelet_tree —
    construction is one stable bit-partition per level, which maps to
    one device SortStable by the current bit (the reference builds the
    node-ordered tree with its sample sort; the level-ordered matrix
    variant is the natural fit for whole-array device partitions and
    supports the same rank/select/access queries). Returns one packed
    bitvector per level, MSB first, each in that level's element order.
    """
    levels = []
    cur = np.asarray(text, dtype=np.uint8)
    for b in reversed(range(bits)):
        bit = (cur >> b) & 1
        levels.append(np.packbits(bit))
        if b == 0:
            break
        # stable partition by the current bit = stable sort on it, run
        # on the device path through the DIA Sort
        d = ctx.Distribute({"v": cur.astype(np.int64),
                            "b": bit.astype(np.int64)})
        got = d.SortStable(key_fn=lambda t: t["b"]).AllGather()
        cur = np.array([int(t["v"]) for t in got], dtype=np.uint8)
    return levels


def wavelet_access(levels, n: int, i: int, bits: int = 8) -> int:
    """Reconstruct the symbol at original position i from the matrix
    (rank-based descent; validates the construction)."""
    sym = 0
    pos = i
    for lvl in range(bits):
        bv = np.unpackbits(levels[lvl])[:n]
        b = int(bv[pos])
        sym = (sym << 1) | b
        if lvl == bits - 1:
            break
        if b == 0:
            pos = int(np.sum(bv[:pos] == 0))
        else:
            pos = int(np.sum(bv == 0)) + int(np.sum(bv[:pos] == 1))
    return sym


def bwt(ctx: Context, text: np.ndarray) -> np.ndarray:
    """Burrows-Wheeler transform via the suffix array
    (reference: examples/suffix_sorting/wavelet_tree / bwt usage)."""
    sa = suffix_array(ctx, text)
    return text[(sa - 1) % len(text)]


def rl_bwt(ctx: Context, text: np.ndarray):
    """Run-length-compressed BWT: (run chars, run lengths).

    Reference: examples/suffix_sorting/rl_bwt.cpp — BWT through the
    suffix array, then run-length encoding of the output (the
    reference encodes via a FlatWindow scan; the host pass here is the
    same boundary-flag + segment-length computation).
    """
    b = bwt(ctx, text)
    if len(b) == 0:
        return np.array([], dtype=text.dtype), np.array([], np.int64)
    starts = np.concatenate([[0], np.flatnonzero(b[1:] != b[:-1]) + 1])
    lengths = np.diff(np.concatenate([starts, [len(b)]]))
    return b[starts], lengths.astype(np.int64)


def check_sa(text: np.ndarray, sa: np.ndarray) -> bool:
    """Linear-time suffix array verification.

    Reference: examples/suffix_sorting/check_sa.hpp — permutation check
    plus the rank trick: sa is correct iff for consecutive entries
    (text[sa[r-1]], rank[sa[r-1]+1]) <= (text[sa[r]], rank[sa[r]+1])
    with the empty suffix ranked smallest.
    """
    n = len(text)
    sa = np.asarray(sa)
    if len(sa) != n:
        return False
    if n == 0:
        return True
    if not np.array_equal(np.sort(sa), np.arange(n)):
        return False
    rank = np.zeros(n + 1, dtype=np.int64)
    rank[sa] = np.arange(1, n + 1)                 # rank[n] = 0 (empty)
    a, b = sa[:-1], sa[1:]
    ca, cb = text[a], text[b]
    ra, rb = rank[a + 1], rank[b + 1]
    return bool(np.all((ca < cb) | ((ca == cb) & (ra < rb))))


def lcp_from_sa(text: np.ndarray, sa: np.ndarray) -> np.ndarray:
    """LCP array (lcp[r] = lcp(suffix sa[r-1], suffix sa[r]), lcp[0]=0)
    by Kasai's algorithm.

    Reference: examples/suffix_sorting/construct_lcp.hpp — the
    reference derives LCP during construction; the Kasai pass here
    yields the identical array from any valid SA in O(n) host time.
    """
    n = len(text)
    lcp = np.zeros(n, dtype=np.int64)
    if n == 0:
        return lcp
    rank = np.zeros(n, dtype=np.int64)
    rank[sa] = np.arange(n)
    h = 0
    for i in range(n):
        r = rank[i]
        if r > 0:
            j = int(sa[r - 1])
            while i + h < n and j + h < n and text[i + h] == text[j + h]:
                h += 1
            lcp[r] = h
            if h > 0:
                h -= 1
        else:
            h = 0
    return lcp


def main():
    import argparse
    parser = argparse.ArgumentParser()
    parser.add_argument("--size", type=int, default=10000)
    args = parser.parse_args()

    from thrill_tpu.api import Run

    def job(ctx):
        rng = np.random.default_rng(0)
        text = rng.integers(97, 101, args.size).astype(np.uint8)
        sa = suffix_array(ctx, text)
        print("suffix array head:", sa[:10])

    Run(job)


if __name__ == "__main__":
    main()
