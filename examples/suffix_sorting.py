"""Suffix array construction by prefix doubling — the Sort-heaviest user.

Reference: /root/reference/examples/suffix_sorting/prefix_doubling.cpp
(also DC3/DC7 in dc3.cpp/dc7.cpp): iterative rank refinement where each
round sorts (rank[i], rank[i+2^k], i) triples — log n distributed sorts.

TPU-native: ranks live as device columns; each doubling round is one
device Sort + neighbor-compare rank assignment (PrefixSum of boundary
flags), the exact structure the reference runs over its sample sort.
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo root on sys.path for CLI runs)


import numpy as np

from thrill_tpu.api import Context


def suffix_array(ctx: Context, text: np.ndarray) -> np.ndarray:
    """text: [n] uint8. Returns the suffix array [n] int64."""
    n = len(text)
    if n == 0:
        return np.array([], dtype=np.int64)

    # initial ranks = byte values; sentinel handling via +1
    rank = text.astype(np.int64) + 1
    idx = np.arange(n, dtype=np.int64)
    h = 1
    while True:
        rank2 = np.zeros(n, dtype=np.int64)
        rank2[:-h if h < n else 0] = rank[h:] if h < n else 0

        d = ctx.Distribute({"i": idx, "r1": rank, "r2": rank2})
        s = d.Sort(key_fn=lambda t: (t["r1"], t["r2"]))
        got = s.AllGather()
        si = np.array([int(t["i"]) for t in got])
        r1 = np.array([int(t["r1"]) for t in got])
        r2 = np.array([int(t["r2"]) for t in got])

        # new ranks: 1 + prefix count of strict (r1, r2) boundaries
        boundary = np.ones(n, dtype=np.int64)
        boundary[1:] = ((r1[1:] != r1[:-1]) | (r2[1:] != r2[:-1])).astype(
            np.int64)
        new_rank_sorted = np.cumsum(boundary)
        rank = np.zeros(n, dtype=np.int64)
        rank[si] = new_rank_sorted
        if new_rank_sorted[-1] == n:
            return si
        h *= 2
        if h >= 2 * n:
            return si


def suffix_array_dense(text: np.ndarray) -> np.ndarray:
    s = bytes(text)
    return np.array(sorted(range(len(s)), key=lambda i: s[i:]),
                    dtype=np.int64)


def bwt(ctx: Context, text: np.ndarray) -> np.ndarray:
    """Burrows-Wheeler transform via the suffix array
    (reference: examples/suffix_sorting/wavelet_tree / bwt usage)."""
    sa = suffix_array(ctx, text)
    return text[(sa - 1) % len(text)]


def main():
    import argparse
    parser = argparse.ArgumentParser()
    parser.add_argument("--size", type=int, default=10000)
    args = parser.parse_args()

    from thrill_tpu.api import Run

    def job(ctx):
        rng = np.random.default_rng(0)
        text = rng.integers(97, 101, args.size).astype(np.uint8)
        sa = suffix_array(ctx, text)
        print("suffix array head:", sa[:10])

    Run(job)


if __name__ == "__main__":
    main()
