"""TeraSort: distributed sort of 100-byte records with 10-byte keys.

Reference: /root/reference/examples/terasort/terasort.cpp:30-43 —
Record { uint8_t key[10]; uint8_t value[90]; }, api::Sort by memcmp on
the key. TPU-native: keys and values live as device byte columns; the
sample sort classifies by two packed uint64 key words.
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo root on sys.path for CLI runs)


import numpy as np

from thrill_tpu.api import Context

KEY_BYTES = 10
VALUE_BYTES = 90


def generate_records(n: int, seed: int = 0):
    """Random TeraGen-style records as a columnar dict."""
    rng = np.random.default_rng(seed)
    return {
        "key": rng.integers(0, 256, size=(n, KEY_BYTES)).astype(np.uint8),
        "value": rng.integers(0, 256, size=(n, VALUE_BYTES)).astype(np.uint8),
    }


def terasort(ctx: Context, records) -> "DIA":
    d = ctx.Distribute(records)
    return d.Sort(key_fn=lambda r: r["key"])


def verify_sorted(out_records) -> bool:
    keys = np.asarray(out_records["key"])
    if len(keys) <= 1:
        return True
    prev, nxt = keys[:-1], keys[1:]
    # lexicographic compare rows
    for i in range(KEY_BYTES):
        lt = prev[:, i] < nxt[:, i]
        gt = prev[:, i] > nxt[:, i]
        if np.any(gt & ~lt):
            # only bad if all previous bytes equal
            eq = np.ones(len(prev), dtype=bool)
            for j in range(i):
                eq &= prev[:, j] == nxt[:, j]
            if np.any(gt & eq):
                return False
    return True


def main():
    import argparse
    import time
    parser = argparse.ArgumentParser(description="thrill_tpu TeraSort")
    parser.add_argument("--records", type=int, default=1_000_000)
    args = parser.parse_args()

    from thrill_tpu.api import Run

    def job(ctx):
        recs = generate_records(args.records)
        t0 = time.perf_counter()
        out = terasort(ctx, recs)
        out.Execute()
        dt = time.perf_counter() - t0
        gb = args.records * 100 / 1e9
        print(f"sorted {args.records} records ({gb:.2f} GB) in {dt:.3f}s "
              f"= {gb / dt:.3f} GB/s")

    Run(job)


if __name__ == "__main__":
    main()
